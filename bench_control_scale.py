"""Control-plane scale bench: per-tenant claimed latency under skew.

Drives thousands of simulated client submissions with skewed tenant
load against a multi-replica claim plane (the real
``server/requests_db`` claim path: two heartbeating replica identities,
several workers each, rendezvous-preferred shards + stealing), and
measures per-tenant ``claimed_at - created_at`` straight from the
durable rows. Scenarios:

* ``hot_tenant`` — the headline: N light tenants trickling while ONE
  hot tenant submits at 100x a light tenant's rate plus an initial
  burst. Reported: pooled light-tenant claimed-latency p50/p99 on the
  fair sharded queue (SKYT_FAIR_QUEUE=1, the default) vs the legacy
  global FIFO (=0), against a no-skew baseline. Acceptance: fair
  light-p99 within 2x of the no-skew baseline; the global queue shows
  the light tenants waiting out the hot backlog.
* ``uniform`` — no-regression guard: aggregate drain throughput and
  trickle submit->claimed p50 at UNIFORM load, fair vs global (the
  fair path's extra per-claim queries must not tax the un-skewed
  case; p50 comparable to BENCH_control_plane_r06's event mode).
* ``zipf`` — Zipf(1.1)-distributed tenant choice over 32 tenants:
  worst-tenant vs median-tenant p99 spread, fair vs global.
* ``pg`` — a scaled-down hot_tenant run against the sqlite-backed
  Postgres stand-in (tests/fake_pg.py) so the shared-DB HA
  configuration is exercised end to end.

CPU-only, no cloud/TPU; one JSON document on stdout (wired into
run_benches.sh -> ``BENCH_control_scale_<suffix>.json``; measured
numbers land in PERF.md + docs/control_plane_scale.md).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), 'tests'))


def _percentile(values, q):
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return round(ordered[idx], 2)


def _fresh_state(tag: str, fair: bool, pg_url=None) -> None:
    root = tempfile.mkdtemp(prefix=f'skyt-bench-scale-{tag}-')
    os.environ['SKYT_STATE_DIR'] = root
    os.environ['SKYT_SERVER_DIR'] = os.path.join(root, 'server')
    os.environ['SKYT_FAIR_QUEUE'] = '1' if fair else '0'
    if pg_url:
        os.environ['SKYT_DB_URL'] = pg_url
    else:
        os.environ.pop('SKYT_DB_URL', None)
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.server import requests_db
    from skypilot_tpu.utils import events
    state_lib._local.__dict__.clear()
    requests_db.reset_db_for_tests()
    events.reset_for_tests()


class ClaimPlane:
    """R replica identities x W worker threads over the real claim
    path (claim -> simulated service -> finalize), with heartbeats and
    rendezvous-preferred shards like the production runner pool."""

    def __init__(self, replicas=2, workers=4, service_ms=0.0):
        from skypilot_tpu.server import requests_db
        from skypilot_tpu.utils import events
        self.rdb = requests_db
        self.events = events
        self.replica_ids = [f'bench-{chr(97 + i)}'
                            for i in range(replicas)]
        self.workers = workers
        self.service_s = service_ms / 1000.0
        self.claims = 0
        self.stop = threading.Event()
        self.threads = []

    def _worker(self, server_id: str) -> None:
        rdb, events = self.rdb, self.events
        cursor = events.cursor(events.REQUESTS)
        prefer = None
        prefer_at = 0.0
        while not self.stop.is_set():
            now = time.monotonic()
            if now >= prefer_at:
                prefer_at = now + 1.0
                try:
                    prefer = rdb.stealing_preference(server_id)
                except Exception:  # pylint: disable=broad-except
                    prefer = None
            try:
                req = rdb.claim_next(rdb.ScheduleType.LONG, server_id,
                                     prefer=prefer)
            except Exception:  # pylint: disable=broad-except
                time.sleep(0.005)
                continue
            if req is None:
                cursor, _ = events.wait_for(events.REQUESTS, cursor,
                                            0.02, stop_event=self.stop)
                continue
            self.claims += 1
            if self.service_s:
                time.sleep(self.service_s)
            rdb.finalize(req.request_id, rdb.RequestStatus.SUCCEEDED,
                         {}, owner=server_id)

    def start(self):
        for sid in self.replica_ids:
            self.rdb.beat(sid)
            for _ in range(self.workers):
                t = threading.Thread(target=self._worker, args=(sid,),
                                     daemon=True)
                t.start()
                self.threads.append(t)

    def beat(self):
        for sid in self.replica_ids:
            try:
                self.rdb.beat(sid)
            except Exception:  # pylint: disable=broad-except
                pass

    def shutdown(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=5)


def _latency_by_ws(rdb):
    conn = rdb._db()  # pylint: disable=protected-access
    rows = conn.execute(
        "SELECT COALESCE(workspace,'default') AS ws, "
        '(claimed_at - created_at) * 1000.0 AS ms FROM requests '
        'WHERE claimed_at IS NOT NULL').fetchall()
    out = {}
    for r in rows:
        out.setdefault(r['ws'], []).append(r['ms'])
    return out


def run_hot_tenant(fair: bool, *, light_tenants=12, light_rate=2.0,
                   duration=14.0, hot_burst=1500, hot_rate=None,
                   service_ms=30.0, replicas=2,
                   workers=4, with_hot=True, drain_cap=150.0,
                   pg_url=None, clients_per_tenant=25) -> dict:
    """One hot-tenant scenario run.

    ``with_hot=True``: N light tenants trickle (Poisson) while ONE hot
    tenant runs at 100x a light tenant's rate for the whole window
    plus an initial queued burst.

    ``with_hot=False`` is the NO-SKEW BASELINE: the standard isolation
    comparison — the SAME sustained aggregate arrival rate spread
    uniformly across (light_tenants + 1) equal tenants, no burst.
    "Within 2x of baseline" then reads: a light tenant keeps (at
    least) the latency it would see if the same traffic came evenly
    from everyone, no matter how concentrated the real load is —
    exactly DRF's isolation property. (An IDLE baseline would be
    meaningless: any saturated system loses to an empty one by the
    free-worker interval alone.)

    Light submissions carry distinct simulated client users
    (thousands of clients across a full bench run)."""
    import random
    tag = ('fair' if fair else 'global') + ('' if with_hot else '-base')
    _fresh_state(tag, fair, pg_url=pg_url)
    from skypilot_tpu.server import requests_db as rdb
    if hot_rate is None:
        hot_rate = 100.0 * light_rate  # the 100x headline multiple
    if not with_hot:
        # Same sustained aggregate, skew removed.
        aggregate = light_tenants * light_rate + hot_rate
        light_tenants = light_tenants + 1
        light_rate = aggregate / light_tenants
    light_ws = [f'light{i}' for i in range(light_tenants)]
    plane = ClaimPlane(replicas=replicas, workers=workers,
                       service_ms=service_ms)
    if with_hot:
        for i in range(hot_burst):
            rdb.create('launch', {'i': i}, rdb.ScheduleType.LONG,
                       user='hot-client', workspace='hot')
    plane.start()
    stop_submit = time.monotonic() + duration
    hot_interval = 1.0 / hot_rate
    submitted = {'light': 0, 'hot': 0}

    def light_submitter(ws: str, seed: int) -> None:
        from skypilot_tpu.sim import traffic
        # Poisson arrivals: periodic submitters would synchronize
        # into a deterministic stream with no queueing at all.
        gaps = traffic.arrival_gaps(random.Random(seed), light_rate)
        seq = 0
        while True:
            time.sleep(next(gaps))
            if time.monotonic() >= stop_submit:
                return
            seq += 1
            client = f'{ws}-client-{seq % clients_per_tenant}'
            rdb.create('launch', {'seq': seq}, rdb.ScheduleType.LONG,
                       user=client, workspace=ws)
            submitted['light'] += 1

    def hot_submitter() -> None:
        # Paced (catch-up) loop: a sleep-per-item loop undershoots the
        # target rate by the scheduler granularity.
        next_at = time.monotonic()
        while True:
            now = time.monotonic()
            if now >= stop_submit:
                return
            if now < next_at:
                time.sleep(min(next_at - now, 0.01))
                continue
            rdb.create('launch', {}, rdb.ScheduleType.LONG,
                       user='hot-client', workspace='hot')
            submitted['hot'] += 1
            next_at += hot_interval

    threads = [threading.Thread(target=light_submitter,
                                args=(ws, 1000 + i), daemon=True)
               for i, ws in enumerate(light_ws)]
    if with_hot:
        threads.append(threading.Thread(target=hot_submitter,
                                        daemon=True))
    for t in threads:
        t.start()
    while time.monotonic() < stop_submit:
        plane.beat()
        time.sleep(1.0)
    for t in threads:
        t.join()
    # Let the plane drain every LIGHT request so tail latencies are
    # measured, not censored (on the global queue this means waiting
    # out the hot backlog — that wait IS the result).
    drain_deadline = time.monotonic() + drain_cap
    censored = 0
    while time.monotonic() < drain_deadline:
        plane.beat()
        pending = rdb.pending_by_workspace()
        if not any(ws in pending for ws in light_ws):
            break
        time.sleep(0.25)
    else:
        pending = rdb.pending_by_workspace()
        censored = sum(pending.get(ws, 0) for ws in light_ws)
    plane.shutdown()
    lat = _latency_by_ws(rdb)
    light_ms = [m for ws in light_ws for m in lat.get(ws, [])]
    hot_ms = lat.get('hot', [])
    achieved_hot_rate = (submitted['hot'] / duration
                         if with_hot else 0.0)
    return {
        'fair_queue': fair,
        'with_hot_tenant': with_hot,
        'light_tenants': light_tenants,
        'light_rate_per_tenant': light_rate,
        'submitted_light': submitted['light'],
        'submitted_hot': submitted['hot'] + (hot_burst if with_hot
                                             else 0),
        'hot_rate_multiple': (round(achieved_hot_rate / light_rate)
                              if with_hot else 0),
        'simulated_clients': light_tenants * clients_per_tenant + 1,
        'light_claimed_p50_ms': _percentile(light_ms, 0.5),
        'light_claimed_p99_ms': _percentile(light_ms, 0.99),
        'hot_claimed_p50_ms': _percentile(hot_ms, 0.5),
        'hot_claimed_p99_ms': _percentile(hot_ms, 0.99),
        'hot_backlog_remaining': pending.get('hot', 0),
        'light_unclaimed_after_cap': censored,
    }


def run_uniform(fair: bool, *, tenants=12, prefill=600,
                workers=4, replicas=2) -> dict:
    """Uniform-load guard: drain throughput + trickle submit->claimed
    p50 (the r06 comparison point) with NO skew."""
    _fresh_state('uniform-' + ('fair' if fair else 'global'), fair)
    from skypilot_tpu.server import requests_db as rdb
    for i in range(prefill):
        rdb.create('launch', {'i': i}, rdb.ScheduleType.LONG,
                   workspace=f'ws{i % tenants}')
    plane = ClaimPlane(replicas=replicas, workers=workers,
                       service_ms=0.0)
    t0 = time.monotonic()
    plane.start()
    while True:
        depths = rdb.pending_depth_by_queue()
        if depths.get('LONG', 0) == 0:
            break
        time.sleep(0.02)
    drain_s = time.monotonic() - t0
    # Trickle: spaced submits against an idle plane -> wake latency.
    trickle = []
    for i in range(25):
        rid = rdb.create('launch', {}, rdb.ScheduleType.LONG,
                         workspace=f'ws{i % tenants}')
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            req = rdb.get(rid)
            if req.claimed_at is not None:
                trickle.append(
                    (req.claimed_at - req.created_at) * 1000.0)
                break
            time.sleep(0.001)
        time.sleep(0.05)
    plane.shutdown()
    return {
        'fair_queue': fair,
        'prefill': prefill,
        'drain_seconds': round(drain_s, 2),
        'claims_per_sec': round(prefill / drain_s, 1),
        'trickle_submit_to_claimed_p50_ms': _percentile(trickle, 0.5),
        'trickle_submit_to_claimed_p99_ms': _percentile(trickle, 0.99),
    }


def run_zipf(fair: bool, *, tenants=32, requests=600, alpha=1.1,
             workers=4, replicas=2, service_ms=5.0) -> dict:
    """Zipf-skewed tenant choice: the many-tenant tail. Reported:
    median-tenant vs worst-tenant claimed p99."""
    import random
    _fresh_state('zipf-' + ('fair' if fair else 'global'), fair)
    from skypilot_tpu.server import requests_db as rdb
    from skypilot_tpu.sim import traffic
    rng = random.Random(1234)
    probs = traffic.zipf_weights(tenants, alpha)
    for _ in range(requests):
        idx = traffic.pick_weighted(rng, probs)
        rdb.create('launch', {}, rdb.ScheduleType.LONG,
                   workspace=f'z{idx}')
    plane = ClaimPlane(replicas=replicas, workers=workers,
                       service_ms=service_ms)
    t0 = time.monotonic()
    plane.start()
    while rdb.pending_depth_by_queue().get('LONG', 0) > 0 and \
            time.monotonic() - t0 < 120:
        time.sleep(0.05)
    plane.shutdown()
    lat = _latency_by_ws(rdb)
    per_tenant_p99 = sorted(
        _percentile(ms, 0.99) for ms in lat.values() if ms)
    return {
        'fair_queue': fair,
        'tenants': tenants,
        'requests': requests,
        'median_tenant_p99_ms':
            per_tenant_p99[len(per_tenant_p99) // 2]
            if per_tenant_p99 else None,
        'worst_tenant_p99_ms': per_tenant_p99[-1]
            if per_tenant_p99 else None,
    }


def main() -> None:
    parser = argparse.ArgumentParser('bench_control_scale')
    parser.add_argument('--quick', action='store_true',
                        help='shrink every scenario (CI smoke)')
    parser.add_argument('--skip-pg', action='store_true')
    args = parser.parse_args()
    scale = 0.33 if args.quick else 1.0
    hot_kw = dict(duration=max(4.0, 14.0 * scale),
                  hot_burst=int(1500 * scale))

    result = {'bench': 'control_scale', 'ts': time.time()}

    baseline = run_hot_tenant(fair=True, with_hot=False, **hot_kw)
    fair = run_hot_tenant(fair=True, with_hot=True, **hot_kw)
    global_q = run_hot_tenant(fair=False, with_hot=True, **hot_kw)
    ratio = None
    if baseline['light_claimed_p99_ms'] and fair['light_claimed_p99_ms']:
        ratio = round(fair['light_claimed_p99_ms'] /
                      baseline['light_claimed_p99_ms'], 2)
    result['hot_tenant'] = {
        'no_skew_baseline': baseline,
        'fair_sharded': fair,
        'global_fifo': global_q,
        'headline_light_p99_fair_over_baseline': ratio,
        'light_p99_global_over_fair':
            round(global_q['light_claimed_p99_ms'] /
                  fair['light_claimed_p99_ms'], 1)
            if (global_q['light_claimed_p99_ms'] and
                fair['light_claimed_p99_ms']) else None,
    }

    uni_fair = run_uniform(fair=True)
    uni_global = run_uniform(fair=False)
    result['uniform'] = {
        'fair_sharded': uni_fair,
        'global_fifo': uni_global,
        'throughput_fair_over_global':
            round(uni_fair['claims_per_sec'] /
                  uni_global['claims_per_sec'], 3),
    }

    result['zipf'] = {
        'fair_sharded': run_zipf(fair=True),
        'global_fifo': run_zipf(fair=False),
    }

    if not args.skip_pg:
        # Shared-DB smoke: the same fair claim plane over the
        # sqlite-backed Postgres stand-in (tests/fake_pg.py). The
        # stand-in's wire layer caps at a few claims/s (every query is
        # a serialized TCP round trip into one sqlite conn), so this
        # arm is protocol fidelity under a hot flood — zero lost
        # light requests — not a latency datapoint.
        try:
            from fake_pg import FakePgServer
            server = FakePgServer()
            try:
                arm = run_hot_tenant(
                    fair=True, light_tenants=4, light_rate=0.3,
                    duration=8.0, hot_burst=20, hot_rate=30.0,
                    service_ms=0.0, replicas=2,
                    workers=1, drain_cap=90.0, pg_url=server.url)
                arm['note'] = ('stand-in wire layer is the '
                               'bottleneck; fidelity smoke only')
                result['pg_standin_hot_tenant'] = arm
            finally:
                server.close()
        except Exception as e:  # pylint: disable=broad-except
            result['pg_standin_hot_tenant'] = {
                'error': f'{type(e).__name__}: {e}'}

    json.dump(result, sys.stdout, indent=1)
    print()


if __name__ == '__main__':
    main()
