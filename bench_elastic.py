"""Elastic recovery bench: relaunch vs shrink, preemption → next step.

Quantifies the ElasticStrategy (jobs/recovery_strategy.py, ISSUE 6)
against the rigid FAILOVER relaunch it replaces for gang-scheduled
multi-slice jobs. Both cases run a real detached managed-job
controller against the fake provider with a resumable step-counter
payload (the checkpoint contract pretrain.py implements for real);
the fake cloud injects a provisioning latency so a full relaunch pays
what a real TPU pod re-provision pays, while an elastic shrink — which
tears down only the dead slice and re-execs on the survivors — does
not.

Measured: wall-clock from ``fake.preempt_slice`` (one slice of a
2-slice gang dies) to the payload's FIRST step after recovery, i.e.
the training downtime a preemption costs.

* ``relaunch`` — rigid FAILOVER: teardown the whole gang, re-provision
  at full size (pays the injected create latency), resume.
* ``shrink``   — elastic: keep the gang, drop the dead slice, re-exec
  on the survivors from the same step counter.

CPU-only, no cloud or TPU access; one JSON document on stdout (wired
into run_benches.sh → ``BENCH_elastic_<suffix>.json``; measured
numbers land in PERF.md and docs/elastic_training.md).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def _setup_env(slow_create: float) -> None:
    home = tempfile.mkdtemp(prefix='skyt-bench-elastic-')
    os.environ['HOME'] = home
    os.environ['SKYT_STATE_DIR'] = os.path.join(home, '.skyt')
    os.environ['SKYT_JOBS_CONTROLLER_POLL'] = '0.2'
    os.environ['SKYT_JOBS_LAUNCH_RETRY_GAP'] = '0.2'
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    del slow_create


# Each incarnation logs one 'start' line, then a 'step N' line per
# step — the bench measures to the first step of the NEW incarnation
# (the old one keeps looping until the controller kills it; real TPU
# ranks would be blocked on dead DCN peers, the stub is not).
_PAYLOAD = (
    'echo start >> "$CKPT.log"; '
    'step=$(cat "$CKPT" 2>/dev/null || echo 0); '
    'while [ "$step" -lt 100000 ]; do '
    '  step=$((step+1)); echo "$step" > "$CKPT"; '
    '  echo "step $step" >> "$CKPT.log"; '
    '  if [ -n "${SKYT_RESIZE_SIGNAL:-}" ] && '
    '     [ -f "$SKYT_RESIZE_SIGNAL" ]; then exit 0; fi; '
    '  sleep 0.05; '
    'done')


def _step(ckpt: str) -> int:
    try:
        with open(ckpt, encoding='utf-8') as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return 0


def _log_lines(ckpt: str) -> list:
    try:
        with open(ckpt + '.log', encoding='utf-8') as f:
            return f.read().splitlines()
    except OSError:
        return []


def _stepped_after_incarnation(ckpt: str, min_starts: int) -> bool:
    """True once incarnation #min_starts (1-based) logged a step."""
    lines = _log_lines(ckpt)
    starts = 0
    for i, line in enumerate(lines):
        if line.startswith('start'):
            starts += 1
            if starts >= min_starts:
                return any(l.startswith('step') for l in lines[i + 1:])
    return False


def _wait(pred, what: str, timeout: float = 90.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise SystemExit(f'bench_elastic: timed out waiting for {what}')


def run_case(elastic: bool, slow_create: float) -> dict:
    from skypilot_tpu.jobs import core as jobs_core
    from skypilot_tpu.jobs import state as jobs_state
    from skypilot_tpu.provision import fake
    from skypilot_tpu.spec.resources import Resources
    from skypilot_tpu.spec.task import Task

    fake.reset()
    # Every run_instances call (initial launch AND any relaunch) pays
    # this — the stand-in for real TPU pod re-provisioning latency.
    # Trim/grow of an existing gang does not call run_instances.
    fake.inject_slow_create(slow_create)

    ckpt = os.path.join(tempfile.mkdtemp(prefix='skyt-bench-el-'), 'ckpt')
    kwargs = {}
    if elastic:
        # grow_check high: the measurement window must see the shrink
        # only, not a concurrent grow-back.
        kwargs['elastic'] = {'min_slices': 1, 'max_slices': 2,
                             'grow_check_seconds': 300,
                             'drain_seconds': 3}
    task = Task(name='bench-el' if elastic else 'bench-rigid',
                run=_PAYLOAD, envs={'CKPT': ckpt},
                resources=Resources(cloud='fake',
                                    accelerators='tpu-v5e-8',
                                    num_slices=2, use_spot=True),
                **kwargs)
    job_id = jobs_core.launch(task)
    _wait(lambda: (jobs_state.get(job_id).status.value == 'RUNNING' and
                   _step(ckpt) >= 2),
          'initial RUNNING + first steps')
    record = jobs_state.get(job_id)

    starts_before = sum(
        1 for l in _log_lines(ckpt) if l.startswith('start'))
    t0 = time.monotonic()
    fake.preempt_slice(record.cluster_name, 1, hosts_per_slice=1)
    _wait(lambda: _stepped_after_incarnation(ckpt, starts_before + 1),
          'first step of the recovered incarnation')
    recovery_seconds = time.monotonic() - t0

    modes = [e['mode'] for e in jobs_state.recovery_events(job_id)]
    jobs_core.cancel(job_id)
    _wait(lambda: jobs_state.get(job_id).status.value == 'CANCELLED',
          'cancel', timeout=30)
    fake.reset()
    return {
        'mode': 'shrink' if elastic else 'relaunch',
        'preempt_to_next_step_seconds': round(recovery_seconds, 3),
        'recovery_modes': modes,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(__doc__)
    parser.add_argument('--slow-create', type=float, default=2.0,
                        help='Injected provisioning latency per '
                             'run_instances call (the cost a relaunch '
                             'pays and a shrink avoids).')
    args = parser.parse_args(argv)
    _setup_env(args.slow_create)

    relaunch = run_case(elastic=False, slow_create=args.slow_create)
    shrink = run_case(elastic=True, slow_create=args.slow_create)
    assert 'shrink' in shrink['recovery_modes'], shrink
    assert 'shrink' not in relaunch['recovery_modes'], relaunch

    result = {
        'bench': 'elastic_recovery',
        'injected_provision_seconds': args.slow_create,
        'relaunch': relaunch,
        'shrink': shrink,
        'speedup': round(
            relaunch['preempt_to_next_step_seconds'] /
            max(shrink['preempt_to_next_step_seconds'], 1e-9), 2),
    }
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    sys.exit(main())
