#!/usr/bin/env python3
"""Bench: SLO-driven predictive autoscaling vs the reactive
``request_rate`` autoscaler (docs/serve_autoscaling.md; artifact
``BENCH_serve_autoscale_<suffix>.json``).

Two parts, both CPU-only:

**1. Fleet simulation** — the REAL autoscaler classes
(``SLOAutoscaler`` + ``mix_policy.plan_mix`` vs
``RequestRateAutoscaler``) driven over a virtual clock against a
two-day diurnal trace with a recurring mid-decline burst and spot
preemptions injected during the burst. Ground truth is a linear
latency–concurrency fleet (p99 = base + slope*c, Little's law),
provisioning takes PROVISION_DELAY simulated seconds, a warm resume
RESUME_DELAY. Both arms see the identical trace, preemption schedule,
hysteresis windows, and per-replica capacity. The reactive arm runs
at THREE tunings: exact (target_qps_per_replica = the SLO-optimal
capacity computed from the ground-truth model — the cheapest possible
reactive fleet, which spends ~30% of the trace out of SLO because
capacity always lands a provision-delay late) and 0.9/0.8 headroom
(what an operator deploys to chase the SLO reactively). Acceptance:
the predictive arm must beat every tuning on SLO-miss seconds and
every headroom tuning on replica-hours. Reported per arm: SLO-miss
seconds (p99 over target, or no capacity while traffic flows),
replica-hours and $-weighted replica-hours (spot vs on-demand rates;
provisioning time is billed, WARM/stopped time is not), warm-pool
resumes.

**2. Warm resume vs cold provision (real stack)** — a scale-to-zero
service on the fake cloud with ``inject_slow_create`` modelling slice
provisioning latency: measures wall-clock time-to-READY for the cold
provision and for the warm-pool resume of the same service.
"""
import json
import math
import os
import sys
import tempfile
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')

# ---------------------------------------------------------------------------
# Part 1: simulation.
# ---------------------------------------------------------------------------

# Ground-truth latency model of one replica (ms).
BASE_MS = 40.0
SLOPE_MS = 8.0
TARGET_P99_MS = 200.0
# Per-replica qps capacity at the SLO boundary (closed form from the
# same inversion the autoscaler uses) — handed to the reactive arm as
# its target_qps_per_replica, i.e. the best static tuning possible.
CAPACITY_QPS = 1000.0 * (TARGET_P99_MS - BASE_MS) / (
    SLOPE_MS * TARGET_P99_MS)

PROVISION_DELAY_S = 120.0     # cold slice provision -> READY
RESUME_DELAY_S = 20.0         # warm (stopped) resume -> READY
TICK_S = 10.0                 # controller cadence
DAY_S = 3600.0                # compressed "day"
DAYS = 2
OD_PRICE_HR = 4.0
SPOT_PRICE_HR = 1.2
SATURATED_MS = 4.0 * TARGET_P99_MS

BURST_START = 1900.0          # recurring, mid-decline (same phase daily)
BURST_END = 2200.0
BURST_QPS = 400.0
PREEMPT_AT = 2050.0           # reclaim half the spot fleet mid-burst


def lam(t: float) -> float:
    """Offered load (qps): diurnal sine + the recurring burst."""
    phase = t % DAY_S
    base = 400.0 + 350.0 * math.sin(2 * math.pi * phase / DAY_S)
    if BURST_START <= phase < BURST_END:
        base += BURST_QPS
    return max(5.0, base)


def fleet_point(qps: float, n_ready: int):
    """(p99_ms, per-replica concurrency) of the ground-truth fleet."""
    if n_ready <= 0:
        return SATURATED_MS, 0.0
    k = 1000.0 * n_ready / max(qps, 1e-9)
    if k <= SLOPE_MS:
        return SATURATED_MS, TARGET_P99_MS / SLOPE_MS * 3
    c = BASE_MS / (k - SLOPE_MS)
    return BASE_MS + SLOPE_MS * c, c


class SimReplica:
    _next_id = [0]

    def __init__(self, now, is_spot, is_fallback=False, delay=None):
        SimReplica._next_id[0] += 1
        self.replica_id = SimReplica._next_id[0]
        self.is_spot = is_spot
        self.is_fallback = is_fallback
        self.ready_at = now + (PROVISION_DELAY_S if delay is None
                               else delay)
        self.state = 'provisioning'
        self.warm_since = None
        self.cloud = self.region = self.zone = None

    @property
    def status(self):
        from skypilot_tpu.serve.serve_state import ReplicaStatus
        return {
            'provisioning': ReplicaStatus.PROVISIONING,
            'ready': ReplicaStatus.READY,
            'warm': ReplicaStatus.WARM,
            'gone': ReplicaStatus.TERMINATED,
            'preempted': ReplicaStatus.PREEMPTED,
        }[self.state]


def run_sim(arm: str, headroom: float = 1.0):
    """arm: 'slo' (predictive) or 'request_rate' (reactive).

    ``headroom`` only affects the reactive arm: its
    target_qps_per_replica is ``CAPACITY_QPS * headroom``. 1.0 is the
    SLO-optimal static tuning (cheapest possible reactive fleet — and
    it spends 30% of the trace out of SLO, because capacity always
    arrives a provision-delay late); 0.9/0.8 are the headroom tunings
    an operator actually deploys to chase the SLO reactively."""
    from skypilot_tpu.serve.autoscalers import (DecisionOp, LoadStats,
                                                RequestRateAutoscaler)
    from skypilot_tpu.serve.service_spec import ServiceSpec
    from skypilot_tpu.serve.slo_autoscaler import SLOAutoscaler

    # Identical knobs both arms: on-demand floor of 1, no dynamic OD
    # backfill (the chaos suite exercises that path; here it would
    # bill double capacity through every transition in the predictive
    # arm only and muddy the forecast-vs-reactive comparison).
    common = dict(min_replicas=1, max_replicas=24,
                  upscale_delay_seconds=0.0,
                  downscale_delay_seconds=120.0,
                  base_ondemand_fallback_replicas=1)
    if arm == 'slo':
        spec = ServiceSpec(target_latency_p99_ms=TARGET_P99_MS,
                           forecaster='seasonal',
                           forecast_horizon_seconds=PROVISION_DELAY_S +
                           TICK_S,
                           **common)
        scaler = SLOAutoscaler(spec)
        scaler.spot_wanted = True
        scaler.warm_pool_size = 4
        scaler.warm_ttl = DAY_S
        # The seasonal ring must match the compressed day.
        from skypilot_tpu.serve.forecast import SeasonalRingForecaster
        scaler.forecaster = SeasonalRingForecaster(
            period_seconds=DAY_S, buckets=72)
    else:
        spec = ServiceSpec(
            target_qps_per_replica=CAPACITY_QPS * headroom, **common)
        scaler = RequestRateAutoscaler(spec)

    SimReplica._next_id[0] = 0
    t = 0.0
    scaler._clock = lambda: t
    replicas = []
    # Warm start both arms identically: the steady-state fleet for the
    # t=0 offered load, already READY.
    n0 = max(1, int(math.ceil(lam(0) / CAPACITY_QPS)))
    for i in range(n0):
        r = SimReplica(t, is_spot=(i > 0), delay=0)
        r.state = 'ready'
        replicas.append(r)
    scaler._target = n0

    miss_s = 0.0
    dollar_hours = 0.0
    replica_hours = 0.0
    warm_hours = 0.0
    warm_resumes = 0
    preempted_total = 0
    preempt_done_day = -1

    while t < DAYS * DAY_S:
        # Preemption schedule: once per day, mid-burst, reclaim half
        # the READY spot fleet (identical in both arms).
        day = int(t // DAY_S)
        if (t % DAY_S) >= PREEMPT_AT and preempt_done_day < day:
            preempt_done_day = day
            spot_ready = [r for r in replicas
                          if r.state == 'ready' and r.is_spot]
            for r in spot_ready[:max(1, len(spot_ready) // 2)]:
                r.state = 'preempted'
                preempted_total += 1

        for r in replicas:
            if r.state == 'provisioning' and t >= r.ready_at:
                r.state = 'ready'

        ready = [r for r in replicas if r.state == 'ready']
        qps = lam(t)
        p99, conc = fleet_point(qps, len(ready))
        latency_ms = {r.replica_id: p99 for r in ready}
        stats = LoadStats(qps=qps, queue_length=conc * len(ready),
                          window_seconds=TICK_S,
                          replica_latency_ms=latency_ms)

        live = [r for r in replicas if r.state != 'gone']
        decisions = scaler.evaluate(stats, live)
        for d in decisions:
            if d.op == DecisionOp.SCALE_UP:
                if d.resume_replica_id is not None:
                    for r in replicas:
                        if (r.replica_id == d.resume_replica_id and
                                r.state == 'warm'):
                            r.state = 'provisioning'
                            r.warm_since = None
                            r.ready_at = t + RESUME_DELAY_S
                            warm_resumes += 1
                            break
                    continue
                for _ in range(d.count):
                    use_spot = d.use_spot
                    if use_spot is None:
                        use_spot = True      # task requested spot
                    replicas.append(SimReplica(
                        t, is_spot=use_spot, is_fallback=d.is_fallback))
            else:
                for r in replicas:
                    if r.replica_id != d.replica_id or r.state in (
                            'gone', 'preempted'):
                        continue
                    if d.warm:
                        r.state = 'warm'
                        r.warm_since = time.time()
                    else:
                        r.state = 'gone'
                        r.warm_since = None

        # Account the tick.
        ready = [r for r in replicas if r.state == 'ready']
        p99, _ = fleet_point(qps, len(ready))
        if qps > 5.0 + 1e-9 or len(ready) == 0:
            if p99 > TARGET_P99_MS + 1e-9:
                miss_s += TICK_S
        for r in replicas:
            if r.state in ('ready', 'provisioning'):
                price = SPOT_PRICE_HR if r.is_spot else OD_PRICE_HR
                dollar_hours += price * TICK_S / 3600.0
                replica_hours += TICK_S / 3600.0
            elif r.state == 'warm':
                warm_hours += TICK_S / 3600.0
        t += TICK_S

    return {
        'slo_miss_seconds': round(miss_s, 1),
        'dollar_weighted_replica_hours': round(dollar_hours, 2),
        'replica_hours': round(replica_hours, 2),
        'warm_pool_hours': round(warm_hours, 2),
        'warm_resumes': warm_resumes,
        'spot_preemptions_injected': preempted_total,
    }


# ---------------------------------------------------------------------------
# Part 2: warm resume vs cold provision on the real serve stack.
# ---------------------------------------------------------------------------


def bench_warm_vs_cold():
    home = tempfile.mkdtemp(prefix='skyt-autoscale-bench-')
    os.environ['HOME'] = home
    os.environ['SKYT_STATE_DIR'] = os.path.join(home, '.skyt')
    os.environ['SKYT_SERVE_CONTROLLER_POLL'] = '0.2'
    os.environ['SKYT_WARM_POOL_SIZE'] = '1'
    os.environ['SKYT_WARM_POOL_TTL'] = '3600'

    from skypilot_tpu.provision import fake
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus
    from skypilot_tpu.spec.resources import Resources
    from skypilot_tpu.spec.task import Task

    fake.reset()
    # Injected create latency stands in for slice provisioning; a warm
    # resume restarts a stopped cluster and skips it.
    fake.inject_slow_create(5.0)

    task = Task(
        name='svc',
        run=('python3 -m http.server "$SKYT_SERVE_REPLICA_PORT" '
             '--bind 127.0.0.1'),
        resources=Resources(cloud='fake', accelerators='tpu-v5e-8'),
        service={
            'readiness_probe': {'path': '/',
                                'initial_delay_seconds': 30,
                                'timeout_seconds': 2},
            'replica_policy': {
                'min_replicas': 0, 'max_replicas': 1,
                'target_latency_p99_ms': 5000,
                'forecast_horizon_seconds': 1,
                'scale_to_zero_idle_seconds': 2.0,
                'upscale_delay_seconds': 0,
                'downscale_delay_seconds': 0,
                'qps_window_seconds': 1,
            },
        })

    def wait_for(predicate, timeout=120, msg=''):
        deadline = time.time() + timeout
        while time.time() < deadline:
            value = predicate()
            if value:
                return value
            time.sleep(0.1)
        raise RuntimeError(f'bench timeout: {msg}')

    serve_core.up(task, 'bench')
    t_up = time.time()
    # Cold path: provision (pays the injected latency) -> READY.
    wait_for(lambda: [r for r in serve_state.list_replicas('bench')
                      if r.status == ReplicaStatus.READY],
             msg='cold READY')
    cold_s = time.time() - t_up
    # Idle out -> WARM (cluster stopped, kept).
    wait_for(lambda: [r for r in serve_state.list_replicas('bench')
                      if r.status == ReplicaStatus.WARM],
             msg='parked WARM')
    endpoint = serve_state.get_service('bench').endpoint
    # Wake: retrying client; time to first 200.
    import urllib.error
    import urllib.request
    t_wake = time.time()
    while time.time() - t_wake < 120:
        try:
            with urllib.request.urlopen(endpoint, timeout=5) as resp:
                if resp.status == 200:
                    break
        except Exception:  # pylint: disable=broad-except
            pass
        time.sleep(0.1)
    else:
        raise RuntimeError('bench timeout: warm wake')
    warm_s = time.time() - t_wake
    serve_core.down('bench', purge=True)
    fake.reset()
    return {
        'injected_provision_latency_s': 5.0,
        'cold_provision_to_ready_s': round(cold_s, 2),
        'warm_resume_to_first_200_s': round(warm_s, 2),
        'speedup': round(cold_s / max(warm_s, 1e-9), 2),
    }


def main():
    out = {
        'bench': 'serve_autoscale',
        'ts': time.time(),
        'sim': {
            'trace': {
                'days': DAYS, 'day_seconds': DAY_S,
                'burst_qps': BURST_QPS,
                'burst_window': [BURST_START, BURST_END],
                'preempt_at': PREEMPT_AT,
                'provision_delay_s': PROVISION_DELAY_S,
                'resume_delay_s': RESUME_DELAY_S,
                'target_p99_ms': TARGET_P99_MS,
                'capacity_qps_per_replica': round(CAPACITY_QPS, 1),
            },
            'reactive_exact': run_sim('request_rate', headroom=1.0),
            'reactive_headroom_0.9': run_sim('request_rate',
                                             headroom=0.9),
            'reactive_headroom_0.8': run_sim('request_rate',
                                             headroom=0.8),
            'predictive_slo': run_sim('slo'),
        },
    }
    sim = out['sim']
    pred = sim['predictive_slo']
    out['warm_vs_cold'] = bench_warm_vs_cold()
    # Acceptance (ISSUE 10): strictly fewer SLO-miss seconds than
    # every request_rate tuning, at equal-or-lower replica-hours than
    # every tuning that actually chases the SLO (headroom arms); the
    # exact-capacity arm is cheaper only by being out of SLO ~30% of
    # the trace, which is reported, not hidden.
    arms = ['reactive_exact', 'reactive_headroom_0.9',
            'reactive_headroom_0.8']
    ok = all(pred['slo_miss_seconds'] < sim[a]['slo_miss_seconds']
             for a in arms)
    ok = ok and all(
        pred['replica_hours'] <= sim[a]['replica_hours']
        for a in ('reactive_headroom_0.9', 'reactive_headroom_0.8'))
    ok = ok and out['warm_vs_cold']['speedup'] > 1.0
    sim['summary'] = {
        'miss_reduction_vs_exact': round(
            sim['reactive_exact']['slo_miss_seconds'] /
            max(pred['slo_miss_seconds'], 1e-9), 2),
        'miss_reduction_vs_headroom_0.9': round(
            sim['reactive_headroom_0.9']['slo_miss_seconds'] /
            max(pred['slo_miss_seconds'], 1e-9), 2),
        'replica_hours_vs_headroom_0.9': round(
            pred['replica_hours'] /
            sim['reactive_headroom_0.9']['replica_hours'], 3),
        'acceptance': 'PASS' if ok else 'FAIL',
    }
    json.dump(out, sys.stdout, indent=2)
    print()
    react = sim['reactive_headroom_0.9']
    print(f"# acceptance: {'PASS' if ok else 'FAIL'} — predictive "
          f"{pred['slo_miss_seconds']}s misses / "
          f"{pred['replica_hours']} replica-h vs request_rate(0.9) "
          f"{react['slo_miss_seconds']}s / {react['replica_hours']} "
          f"replica-h (exact-tuned: "
          f"{sim['reactive_exact']['slo_miss_seconds']}s / "
          f"{sim['reactive_exact']['replica_hours']} replica-h); warm "
          f"resume {out['warm_vs_cold']['speedup']}x faster to READY",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
