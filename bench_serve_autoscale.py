#!/usr/bin/env python3
"""Bench: SLO-driven predictive autoscaling vs the reactive
``request_rate`` autoscaler (docs/serve_autoscaling.md; artifact
``BENCH_serve_autoscale_<suffix>.json``).

Two parts, both CPU-only:

**1. Fleet simulation** — the REAL autoscaler classes
(``SLOAutoscaler`` + ``mix_policy.plan_mix`` vs
``RequestRateAutoscaler``) A/B'd through ``skypilot_tpu.sim`` (the
r16 simkit, whose fleet model this bench's r11 hand-rolled trace loop
was the ancestor of): each arm is a declarative Scenario sharing one
two-day diurnal trace with a recurring mid-decline burst and a
half-the-spot-fleet reclaim injected during each day's burst, run
through the same ``run_scenario`` the tier-1 invariant tests use —
same seed, so both arms see the identical Poisson arrival sequence.
Ground truth is the sim's linear latency–concurrency fleet (p99 =
base + slope*c, Little's law), provisioning takes PROVISION_DELAY
simulated seconds, a warm resume RESUME_DELAY. The reactive arm runs
at THREE tunings: exact (target_qps_per_replica = the SLO-optimal
capacity computed from the ground-truth model — the cheapest possible
reactive fleet, which spends much of the trace out of SLO because
capacity always lands a provision-delay late) and 0.9/0.8 headroom
(what an operator deploys to chase the SLO reactively). Acceptance:
the predictive arm must beat every tuning on SLO-miss seconds and
every headroom tuning on replica-hours. Reported per arm: SLO-miss
seconds (p99 over target, or no capacity while traffic flows),
replica-hours and $-weighted replica-hours (spot vs on-demand rates;
provisioning time is billed, WARM/stopped time is not), warm-pool
resumes, and the run's reproducibility digest.

**2. Warm resume vs cold provision (real stack)** — a scale-to-zero
service on the fake cloud with ``inject_slow_create`` modelling slice
provisioning latency: measures wall-clock time-to-READY for the cold
provision and for the warm-pool resume of the same service.
"""
import json
import math
import os
import sys
import tempfile
import time

os.environ.setdefault('JAX_PLATFORMS', 'cpu')

# ---------------------------------------------------------------------------
# Part 1: simulation.
# ---------------------------------------------------------------------------

# Ground-truth latency model of one replica (ms).
BASE_MS = 40.0
SLOPE_MS = 8.0
TARGET_P99_MS = 200.0
# Per-replica qps capacity at the SLO boundary (closed form from the
# same inversion the autoscaler uses) — handed to the reactive arm as
# its target_qps_per_replica, i.e. the best static tuning possible.
CAPACITY_QPS = 1000.0 * (TARGET_P99_MS - BASE_MS) / (
    SLOPE_MS * TARGET_P99_MS)

PROVISION_DELAY_S = 120.0     # cold slice provision -> READY
RESUME_DELAY_S = 20.0         # warm (stopped) resume -> READY
TICK_S = 10.0                 # controller cadence
DAY_S = 3600.0                # compressed "day"
DAYS = 2
OD_PRICE_HR = 4.0
SPOT_PRICE_HR = 1.2
SATURATED_MS = 4.0 * TARGET_P99_MS

BURST_START = 1900.0          # recurring, mid-decline (same phase daily)
BURST_END = 2200.0
BURST_QPS = 400.0
PREEMPT_AT = 2050.0           # reclaim half the spot fleet mid-burst


SEED = 11                     # one seed: both arms see one arrival trace


def lam(t: float) -> float:
    """Offered load (qps): diurnal sine + the recurring burst (used
    for the warm-start fleet size; the scenario tenants below express
    the same trace declaratively)."""
    phase = t % DAY_S
    base = 400.0 + 350.0 * math.sin(2 * math.pi * phase / DAY_S)
    if BURST_START <= phase < BURST_END:
        base += BURST_QPS
    return max(5.0, base)


def _arm_scenario(arm: str, headroom: float):
    """One bench arm as a simkit Scenario: same trace, seed, fleet
    physics, and fault timeline for every arm — only the ``service``
    block (which autoscaler runs) differs."""
    from skypilot_tpu.sim import Scenario

    service = dict(min_replicas=1, max_replicas=24,
                   upscale_delay_seconds=0.0,
                   downscale_delay_seconds=120.0,
                   base_ondemand_fallback_replicas=1)
    autoscaler = {}
    if arm == 'slo':
        service.update(target_latency_p99_ms=TARGET_P99_MS,
                       forecaster='seasonal',
                       forecast_horizon_seconds=PROVISION_DELAY_S +
                       TICK_S)
        # The seasonal ring must match the compressed day.
        autoscaler = {'warm_pool_size': 4, 'warm_ttl': DAY_S,
                      'spot_wanted': True,
                      'seasonal_period_s': DAY_S,
                      'seasonal_buckets': 72}
    else:
        service.update(
            target_qps_per_replica=CAPACITY_QPS * headroom)
        # from_spec would wrap the OD floor in FallbackAutoscaler;
        # this arm IS the plain reactive scaler.
        autoscaler = {'kind': 'request_rate'}

    # Warm start both arms identically: the steady-state fleet for the
    # t=0 offered load plus one replica of headroom (launching exactly
    # at capacity saturates the fluid queue on tick one and starves
    # the SLO arm's latency model of unclamped samples), already
    # READY, first replica on-demand.
    n0 = max(1, int(math.ceil(lam(0) / CAPACITY_QPS))) + 1
    return Scenario.from_dict({
        'name': f'serve_autoscale_{arm}_{headroom:g}',
        'seed': SEED,
        'duration_s': DAYS * DAY_S,
        'tick_s': TICK_S,
        'service': service,
        'autoscaler': autoscaler,
        'fleet': {
            'initial_replicas': n0,
            'base_latency_ms': BASE_MS,
            'latency_slope_ms': SLOPE_MS,
            'provision_delay_s': PROVISION_DELAY_S,
            'resume_delay_s': RESUME_DELAY_S,
            'spot': True,
            'od_price_hr': OD_PRICE_HR,
            # Both arms graded against the same ground-truth SLO line
            # (the reactive arm's spec doesn't carry it).
            'slo_target_p99_ms': TARGET_P99_MS,
            'max_queue_per_replica': 200.0,
            'domains': [{'cloud': 'fake', 'region': 'r1', 'zone': 'a',
                         'price': SPOT_PRICE_HR}],
        },
        'tenants': [
            {'name': 'diurnal',
             'rate': {'shape': 'diurnal', 'base_qps': 400.0,
                      'amplitude_qps': 350.0, 'period_s': DAY_S}},
        ] + [
            {'name': f'burst_day{day}',
             'rate': {'shape': 'burst',
                      'start_s': day * DAY_S + BURST_START,
                      'end_s': day * DAY_S + BURST_END,
                      'qps': BURST_QPS}}
            for day in range(DAYS)
        ],
        # Once per day, mid-burst: reclaim half the live spot fleet.
        'faults': [
            {'at': day * DAY_S + PREEMPT_AT, 'kind': 'spot_reclaim',
             'fraction': 0.5}
            for day in range(DAYS)
        ],
    })


def run_sim(arm: str, headroom: float = 1.0):
    """arm: 'slo' (predictive) or 'request_rate' (reactive).

    ``headroom`` only affects the reactive arm: its
    target_qps_per_replica is ``CAPACITY_QPS * headroom``. 1.0 is the
    SLO-optimal static tuning (cheapest possible reactive fleet — and
    it spends much of the trace out of SLO, because capacity always
    arrives a provision-delay late); 0.9/0.8 are the headroom tunings
    an operator actually deploys to chase the SLO reactively."""
    from skypilot_tpu.sim import run_scenario

    report = run_scenario(_arm_scenario(arm, headroom))
    s = report.summary
    return {
        'slo_miss_seconds': s['slo_miss_seconds'],
        'dollar_weighted_replica_hours':
            s['dollar_weighted_replica_hours'],
        'replica_hours': s['replica_hours'],
        'warm_pool_hours': s['warm_pool_hours'],
        'warm_resumes': s['warm_resumes'],
        'spot_preemptions_injected': s['preemptions'],
        'shed_requests': s['shed_total'],
        'digest': report.digest(),
    }


# ---------------------------------------------------------------------------
# Part 2: warm resume vs cold provision on the real serve stack.
# ---------------------------------------------------------------------------


def bench_warm_vs_cold():
    home = tempfile.mkdtemp(prefix='skyt-autoscale-bench-')
    os.environ['HOME'] = home
    os.environ['SKYT_STATE_DIR'] = os.path.join(home, '.skyt')
    os.environ['SKYT_SERVE_CONTROLLER_POLL'] = '0.2'
    os.environ['SKYT_WARM_POOL_SIZE'] = '1'
    os.environ['SKYT_WARM_POOL_TTL'] = '3600'

    from skypilot_tpu.provision import fake
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus
    from skypilot_tpu.spec.resources import Resources
    from skypilot_tpu.spec.task import Task

    fake.reset()
    # Injected create latency stands in for slice provisioning; a warm
    # resume restarts a stopped cluster and skips it.
    fake.inject_slow_create(5.0)

    task = Task(
        name='svc',
        run=('python3 -m http.server "$SKYT_SERVE_REPLICA_PORT" '
             '--bind 127.0.0.1'),
        resources=Resources(cloud='fake', accelerators='tpu-v5e-8'),
        service={
            'readiness_probe': {'path': '/',
                                'initial_delay_seconds': 30,
                                'timeout_seconds': 2},
            'replica_policy': {
                'min_replicas': 0, 'max_replicas': 1,
                'target_latency_p99_ms': 5000,
                'forecast_horizon_seconds': 1,
                'scale_to_zero_idle_seconds': 2.0,
                'upscale_delay_seconds': 0,
                'downscale_delay_seconds': 0,
                'qps_window_seconds': 1,
            },
        })

    def wait_for(predicate, timeout=120, msg=''):
        deadline = time.time() + timeout
        while time.time() < deadline:
            value = predicate()
            if value:
                return value
            time.sleep(0.1)
        raise RuntimeError(f'bench timeout: {msg}')

    serve_core.up(task, 'bench')
    t_up = time.time()
    # Cold path: provision (pays the injected latency) -> READY.
    wait_for(lambda: [r for r in serve_state.list_replicas('bench')
                      if r.status == ReplicaStatus.READY],
             msg='cold READY')
    cold_s = time.time() - t_up
    # Idle out -> WARM (cluster stopped, kept).
    wait_for(lambda: [r for r in serve_state.list_replicas('bench')
                      if r.status == ReplicaStatus.WARM],
             msg='parked WARM')
    endpoint = serve_state.get_service('bench').endpoint
    # Wake: retrying client; time to first 200.
    import urllib.error
    import urllib.request
    t_wake = time.time()
    while time.time() - t_wake < 120:
        try:
            with urllib.request.urlopen(endpoint, timeout=5) as resp:
                if resp.status == 200:
                    break
        except Exception:  # pylint: disable=broad-except
            pass
        time.sleep(0.1)
    else:
        raise RuntimeError('bench timeout: warm wake')
    warm_s = time.time() - t_wake
    serve_core.down('bench', purge=True)
    fake.reset()
    return {
        'injected_provision_latency_s': 5.0,
        'cold_provision_to_ready_s': round(cold_s, 2),
        'warm_resume_to_first_200_s': round(warm_s, 2),
        'speedup': round(cold_s / max(warm_s, 1e-9), 2),
    }


def main():
    out = {
        'bench': 'serve_autoscale',
        'ts': time.time(),
        'sim': {
            'trace': {
                'days': DAYS, 'day_seconds': DAY_S,
                'burst_qps': BURST_QPS,
                'burst_window': [BURST_START, BURST_END],
                'preempt_at': PREEMPT_AT,
                'provision_delay_s': PROVISION_DELAY_S,
                'resume_delay_s': RESUME_DELAY_S,
                'target_p99_ms': TARGET_P99_MS,
                'capacity_qps_per_replica': round(CAPACITY_QPS, 1),
            },
            'reactive_exact': run_sim('request_rate', headroom=1.0),
            'reactive_headroom_0.9': run_sim('request_rate',
                                             headroom=0.9),
            'reactive_headroom_0.8': run_sim('request_rate',
                                             headroom=0.8),
            'predictive_slo': run_sim('slo'),
        },
    }
    sim = out['sim']
    pred = sim['predictive_slo']
    out['warm_vs_cold'] = bench_warm_vs_cold()
    # Acceptance (ISSUE 10): strictly fewer SLO-miss seconds than
    # every request_rate tuning, at equal-or-lower replica-hours than
    # every tuning that actually chases the SLO (headroom arms); the
    # exact-capacity arm is cheaper only by being out of SLO ~30% of
    # the trace, which is reported, not hidden.
    arms = ['reactive_exact', 'reactive_headroom_0.9',
            'reactive_headroom_0.8']
    ok = all(pred['slo_miss_seconds'] < sim[a]['slo_miss_seconds']
             for a in arms)
    ok = ok and all(
        pred['replica_hours'] <= sim[a]['replica_hours']
        for a in ('reactive_headroom_0.9', 'reactive_headroom_0.8'))
    ok = ok and out['warm_vs_cold']['speedup'] > 1.0
    sim['summary'] = {
        'miss_reduction_vs_exact': round(
            sim['reactive_exact']['slo_miss_seconds'] /
            max(pred['slo_miss_seconds'], 1e-9), 2),
        'miss_reduction_vs_headroom_0.9': round(
            sim['reactive_headroom_0.9']['slo_miss_seconds'] /
            max(pred['slo_miss_seconds'], 1e-9), 2),
        'replica_hours_vs_headroom_0.9': round(
            pred['replica_hours'] /
            sim['reactive_headroom_0.9']['replica_hours'], 3),
        'acceptance': 'PASS' if ok else 'FAIL',
    }
    json.dump(out, sys.stdout, indent=2)
    print()
    react = sim['reactive_headroom_0.9']
    print(f"# acceptance: {'PASS' if ok else 'FAIL'} — predictive "
          f"{pred['slo_miss_seconds']}s misses / "
          f"{pred['replica_hours']} replica-h vs request_rate(0.9) "
          f"{react['slo_miss_seconds']}s / {react['replica_hours']} "
          f"replica-h (exact-tuned: "
          f"{sim['reactive_exact']['slo_miss_seconds']}s / "
          f"{sim['reactive_exact']['replica_hours']} replica-h); warm "
          f"resume {out['warm_vs_cold']['speedup']}x faster to READY",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
