#!/usr/bin/env python3
"""Weight-distribution bench: binary-tree peer fan-out vs bucket-direct
cold start, through the real FanoutPuller/manifest stack against
bandwidth-throttled in-process sources (ISSUE 17).

CPU-only; no cloud credentials. The physics under test: the bucket is
one origin with a fixed aggregate uplink, while every weight-complete
peer adds its own uplink — so bucket-direct cold start is O(N) in
fleet size and fan-out is O(log N). Arms:

1. cold start at 1 / 8 / 64 replicas: every replica pulls the full
   manifest; bucket-direct (all N convoy on the origin) vs fan-out
   (tree peers + lease-bounded bucket reads). Acceptance: fan-out
   beats bucket-direct at 64 replicas.
2. heal latency: 8-replica fan-out with one peer killed mid-transfer —
   children heal up the ancestor chain; the fleet must still converge.
3. warm delta refresh: re-pull after 1 of 4 shards changed at the
   source — only the changed shard moves.

Emits one JSON document on stdout; run_benches.sh tees it into
``BENCH_fanout_<suffix>.json`` and the tables land in PERF.md.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

from skypilot_tpu.data import ckpt_manifest
from skypilot_tpu.data import fanout

ITERS = 3
SHARDS = 4
SHARD_BYTES = 256 * 1024            # 1 MiB of weights per replica
BUCKET_BW = 16 * 1024 * 1024        # origin aggregate uplink, bytes/s
PEER_BW = 16 * 1024 * 1024          # per-peer uplink, bytes/s


def p50(samples):
    return sorted(samples)[len(samples) // 2]


class Throttle:
    """Shared-pipe model: every transfer through one instance is
    serialized onto `rate` bytes/s of aggregate bandwidth, so N
    concurrent readers each see rate/N."""

    def __init__(self, rate: float) -> None:
        self.rate = float(rate)
        self._lock = threading.Lock()
        self._ready_at = time.monotonic()
        self.bytes = 0

    def take(self, nbytes: int) -> None:
        with self._lock:
            self.bytes += nbytes
            now = time.monotonic()
            start = max(now, self._ready_at)
            self._ready_at = start + nbytes / self.rate
            delay = self._ready_at - now
        if delay > 0:
            time.sleep(delay)


def make_weights(root: str) -> dict:
    os.makedirs(root, exist_ok=True)
    for i in range(SHARDS):
        with open(os.path.join(root, f'shard-{i}.bin'), 'wb') as f:
            f.write(os.urandom(SHARD_BYTES))
    payload = ckpt_manifest.build(root, step=17)
    ckpt_manifest.write(root, payload)
    return payload


def dir_source(name, root, throttle, is_peer=True, gate=None,
               kill_after=None):
    """Serve shards out of `root` through `throttle`. `gate` (an
    Event) models a peer that only serves once its own pull finished;
    `kill_after` kills the peer after that many fetches
    (mid-transfer death for the heal arm)."""
    calls = [0]

    def fn(shard, offset):
        if gate is not None and not gate.wait(timeout=60):
            raise fanout.PeerUnavailable(f'{name} never became ready')
        calls[0] += 1
        if kill_after is not None and calls[0] > kill_after:
            raise fanout.PeerUnavailable(f'{name} died mid-transfer')
        with open(os.path.join(root, shard['path']), 'rb') as f:
            f.seek(offset)
            data = f.read()
        throttle.take(len(data))
        return data

    return fanout.CallableSource(name, fn, is_peer=is_peer)


def cold_start(n, src, manifest, work, *, tree, dead_peer=None):
    """Launch n replicas at t=0; return (makespan, per-replica times,
    total heals). `tree=False` = bucket-direct convoy (no peers, no
    lease)."""
    bucket_throttle = Throttle(BUCKET_BW)
    peer_throttles = {}
    ready = [threading.Event() for _ in range(n)]
    dests = [os.path.join(work, f'replica-{i}') for i in range(n)]
    lease = (fanout.LeaseManager(fanout.bucket_lease_bound(n), ttl=300)
             if tree else None)
    times = [0.0] * n
    heals = [0] * n
    errors = []

    def run(pos):
        started = time.monotonic()
        try:
            sources = []
            if tree:
                for anc in fanout.tree_ancestors(pos):
                    throttle = peer_throttles.setdefault(
                        anc, Throttle(PEER_BW))
                    sources.append(dir_source(
                        f'peer:{anc}', dests[anc], throttle,
                        gate=ready[anc],
                        kill_after=(2 if anc == dead_peer else None)))
            puller = fanout.FanoutPuller(
                manifest, dests[pos], sources,
                dir_source('bucket', src, bucket_throttle,
                           is_peer=False),
                lease=lease, holder=f'replica-{pos}')
            result = puller.pull()
            heals[pos] = int(result['heals'])
            times[pos] = time.monotonic() - started
            ready[pos].set()
        except BaseException as exc:  # pragma: no cover - bench guard
            errors.append(f'replica {pos}: {exc!r}')
            ready[pos].set()

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n)]
    started = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    makespan = time.monotonic() - started
    if errors:
        raise RuntimeError('; '.join(errors[:3]))
    for d in dests:
        if ckpt_manifest.read(d) is None:
            raise RuntimeError(f'{d}: manifest never committed')
    return makespan, times, sum(heals), bucket_throttle.bytes


def bench_cold_start(src, manifest, tmp):
    out = {}
    for n in (1, 8, 64):
        direct, fanned = [], []
        for i in range(ITERS):
            work = os.path.join(tmp, f'direct-{n}-{i}')
            direct.append(cold_start(n, src, manifest, work,
                                     tree=False)[0])
            shutil.rmtree(work)
            work = os.path.join(tmp, f'fanout-{n}-{i}')
            fanned.append(cold_start(n, src, manifest, work,
                                     tree=True)[0])
            shutil.rmtree(work)
        out[str(n)] = {
            'bucket_direct_makespan_s': round(p50(direct), 3),
            'fanout_makespan_s': round(p50(fanned), 3),
            'speedup': round(p50(direct) / p50(fanned), 2),
        }
    return out


def bench_heal(src, manifest, tmp):
    clean = cold_start(8, src, manifest, os.path.join(tmp, 'h-clean'),
                       tree=True)
    healed = cold_start(8, src, manifest, os.path.join(tmp, 'h-dead'),
                        tree=True, dead_peer=1)
    return {
        'clean_makespan_s': round(clean[0], 3),
        'dead_peer_makespan_s': round(healed[0], 3),
        'heal_events': healed[2],
        'converged': True,  # cold_start raises otherwise
    }


def bench_warm_delta(src, manifest, tmp):
    dest = os.path.join(tmp, 'warm')
    throttle = Throttle(BUCKET_BW)

    def pull(payload):
        started = time.monotonic()
        result = fanout.FanoutPuller(
            payload, dest, [],
            dir_source('bucket', src, throttle, is_peer=False)).pull()
        return time.monotonic() - started, result

    cold_s, cold = pull(manifest)
    with open(os.path.join(src, 'shard-0.bin'), 'wb') as f:
        f.write(os.urandom(SHARD_BYTES))
    refreshed = ckpt_manifest.build(src, step=18)
    ckpt_manifest.write(src, refreshed)
    warm_s, warm = pull(refreshed)
    return {
        'cold_s': round(cold_s, 3),
        'warm_s': round(warm_s, 3),
        'cold_fetched': cold['fetched'],
        'warm_fetched': warm['fetched'],
        'warm_skipped': warm['skipped'],
    }


def main() -> int:
    tmp = tempfile.mkdtemp(prefix='skyt-fanout-bench-')
    try:
        src = os.path.join(tmp, 'bucket')
        manifest = make_weights(src)
        doc = {
            'bench': 'weight_fanout',
            'config': {
                'shards': SHARDS, 'shard_bytes': SHARD_BYTES,
                'bucket_bw_mibs': BUCKET_BW / 2**20,
                'peer_bw_mibs': PEER_BW / 2**20, 'iters': ITERS,
            },
            'cold_start': bench_cold_start(src, manifest, tmp),
            'heal': bench_heal(src, manifest, tmp),
            'warm_delta': bench_warm_delta(src, manifest, tmp),
        }
        at64 = doc['cold_start']['64']
        doc['acceptance'] = {
            'fanout_beats_bucket_direct_at_64': at64['speedup'] > 1.0,
        }
        json.dump(doc, sys.stdout, indent=1)
        print()
        return 0 if at64['speedup'] > 1.0 else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == '__main__':
    sys.exit(main())
